"""Op-test burn-down, batch 5 (VERDICT r1 #3): manipulation (gather/scatter/
pad/slice families), search/sort, stat, sequence ops (padded+mask LoD
equivalents), metric ops — numpy-referenced, grads where defined."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import functional as F

from op_test import OpTest

rng = np.random.RandomState(23)


def _randn(*shape):
    return rng.randn(*shape).astype(np.float32)


X = _randn(4, 5)
M = _randn(6)
IDX = np.array([2, 0, 3], np.int64)
I2D = rng.randint(0, 4, (4, 5)).astype(np.int64)


def _np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


CASES = [
    # --- manipulation -------------------------------------------------------
    ("concat", lambda a, b: paddle.concat([a, b], axis=0),
     {"a": X, "b": X}, {}, [np.concatenate([X, X], 0)], ["a", "b"]),
    ("stack", lambda a, b: paddle.stack([a, b], axis=1),
     {"a": X, "b": X}, {}, [np.stack([X, X], 1)], ["a", "b"]),
    ("unstack", lambda x: paddle.unstack(x, axis=0)[1],
     {"x": X[:2]}, {}, [X[1]], None),
    ("unbind", lambda x: paddle.unbind(x, axis=1)[2],
     {"x": X}, {}, [X[:, 2]], ["x"]),
    ("split", lambda x: paddle.split(x, 2, axis=1)[0] if True else None,
     {"x": _randn(4, 6)}, {}, None, ["x"]),
    ("chunk", lambda x: paddle.chunk(x, 2, axis=0)[1],
     {"x": X}, {}, [X[2:]], ["x"]),
    ("tile", paddle.tile, {"x": X}, {"repeat_times": [2, 1]},
     [np.tile(X, (2, 1))], ["x"]),
    ("broadcast_to", paddle.broadcast_to, {"x": X[:1]}, {"shape": [4, 5]},
     [np.broadcast_to(X[:1], (4, 5))], ["x"]),
    ("expand_as", paddle.expand_as, {"x": X[:1], "y": X}, {},
     [np.broadcast_to(X[:1], X.shape)], None),
    ("flip", paddle.flip, {"x": X}, {"axis": [0]}, [X[::-1]], ["x"]),
    ("roll", paddle.roll, {"x": X}, {"shifts": 2, "axis": 0},
     [np.roll(X, 2, 0)], ["x"]),
    ("rot90", paddle.rot90, {"x": X}, {}, [np.rot90(X)], None),
    ("repeat_interleave", paddle.repeat_interleave, {"x": X},
     {"repeats": 2, "axis": 0}, [np.repeat(X, 2, 0)], ["x"]),
    ("squeeze", paddle.squeeze, {"x": X[:, None]}, {"axis": 1}, [X], ["x"]),
    ("unsqueeze", paddle.unsqueeze, {"x": X}, {"axis": 0}, [X[None]], ["x"]),
    ("flatten", paddle.flatten, {"x": _randn(2, 3, 4)},
     {"start_axis": 1, "stop_axis": 2}, None, ["x"]),
    ("reshape", paddle.reshape, {"x": X}, {"shape": [5, 4]},
     [X.reshape(5, 4)], ["x"]),
    ("transpose", paddle.transpose, {"x": X}, {"perm": [1, 0]}, [X.T], ["x"]),
    ("moveaxis", paddle.moveaxis, {"x": _randn(2, 3, 4)},
     {"source": 0, "destination": 2}, None, ["x"]),
    ("gather", paddle.gather, {"x": X, "index": IDX}, {}, [X[IDX]], ["x"]),
    ("gather_axis1", paddle.gather, {"x": X, "index": IDX}, {"axis": 1},
     [X[:, IDX]], ["x"]),
    ("gather_nd", paddle.gather_nd,
     {"x": X, "index": np.array([[0, 1], [3, 2]], np.int64)}, {},
     [X[[0, 3], [1, 2]]], ["x"]),
    ("index_select", paddle.index_select, {"x": X, "index": IDX}, {},
     [X[IDX]], ["x"]),
    ("index_sample", paddle.index_sample,
     {"x": X, "index": I2D[:, :3]}, {},
     [np.take_along_axis(X, I2D[:, :3], axis=1)], None),
    ("take_along_axis", paddle.take_along_axis,
     {"x": X, "indices": I2D[:, :2]}, {"axis": 1},
     [np.take_along_axis(X, I2D[:, :2], axis=1)], None),
    ("scatter", paddle.scatter,
     {"x": X, "index": np.array([1, 3], np.int64), "updates": _randn(2, 5)},
     {}, None, None),
    ("masked_select", paddle.masked_select,
     {"x": M, "mask": np.array([1, 0, 1, 1, 0, 1], bool)}, {},
     [M[[0, 2, 3, 5]]], None),
    ("masked_fill", paddle.masked_fill,
     {"x": X, "mask": X > 0}, {"value": -1.0},
     [np.where(X > 0, -1.0, X)], None),
    ("where", paddle.where, {"cond": X > 0, "x": X, "y": X * 0}, {},
     [np.where(X > 0, X, 0)], None),
    ("tril", paddle.tril, {"x": X[:4, :4]}, {}, [np.tril(X[:4, :4])], ["x"]),
    ("triu", paddle.triu, {"x": X[:4, :4]}, {}, [np.triu(X[:4, :4])], ["x"]),
    ("diag", paddle.diag, {"x": M[:4]}, {}, [np.diag(M[:4])], None),
    ("diagflat", paddle.diagflat, {"x": M[:3]}, {}, [np.diagflat(M[:3])],
     None),
    ("pad_2d", lambda x: F.pad(x, [1, 1, 2, 0]),
     {"x": X}, {}, [np.pad(X, ((1, 1), (2, 0)))], ["x"]),
    # --- search / sort ------------------------------------------------------
    ("argmax", paddle.argmax, {"x": X}, {"axis": 1}, [X.argmax(1)], None),
    ("argmin", paddle.argmin, {"x": X}, {"axis": 0}, [X.argmin(0)], None),
    ("argsort", paddle.argsort, {"x": M}, {}, [np.argsort(M)], None),
    ("argsort_desc", paddle.argsort, {"x": M}, {"descending": True},
     [np.argsort(-M)], None),
    ("sort", paddle.sort, {"x": M}, {}, [np.sort(M)], None),
    ("sort_axis0", paddle.sort, {"x": X}, {"axis": 0}, [np.sort(X, 0)],
     ["x"]),
    ("topk", lambda x: paddle.topk(x, k=3)[0], {"x": M}, {},
     [np.sort(M)[::-1][:3]], None),
    ("topk_idx", lambda x: paddle.topk(x, k=3)[1], {"x": M}, {},
     [np.argsort(-M)[:3]], None),
    ("searchsorted", paddle.searchsorted,
     {"sorted": np.sort(M), "values": np.array([0.0, 1.0], np.float32)}, {},
     [np.searchsorted(np.sort(M), np.array([0.0, 1.0]))], None),
    ("kthvalue", lambda x: paddle.kthvalue(x, k=2)[0], {"x": M}, {},
     [np.sort(M)[1]], None),
    ("mode", lambda x: paddle.mode(x)[0],
     {"x": np.array([[1.0, 2.0, 2.0], [3.0, 3.0, 1.0]], np.float32)}, {},
     [np.array([2.0, 3.0], np.float32)], None),
    ("nonzero", paddle.nonzero,
     {"x": np.array([0.0, 1.0, 0.0, 2.0], np.float32)}, {},
     [np.array([[1], [3]], np.int64)], None),
    ("unique", lambda x: paddle.unique(x),
     {"x": np.array([3.0, 1.0, 3.0, 2.0], np.float32)}, {},
     [np.array([1.0, 2.0, 3.0], np.float32)], None),
    ("unique_consecutive", lambda x: paddle.unique_consecutive(x),
     {"x": np.array([1.0, 1.0, 2.0, 2.0, 1.0], np.float32)}, {},
     [np.array([1.0, 2.0, 1.0], np.float32)], None),
    # --- stat ---------------------------------------------------------------
    ("std", paddle.std, {"x": X}, {}, [X.std(ddof=1)], None),
    ("std_axis", paddle.std, {"x": X}, {"axis": 1}, [X.std(1, ddof=1)],
     ["x"]),
    ("var", paddle.var, {"x": X}, {}, [X.var(ddof=1)], ["x"]),
    ("median", paddle.median, {"x": M}, {}, [np.median(M)], None),
    ("quantile", paddle.quantile, {"x": M}, {"q": 0.5},
     [np.quantile(M, 0.5)], None),
    ("bincount", paddle.bincount,
     {"x": np.array([0, 1, 1, 3], np.int64)}, {},
     [np.bincount(np.array([0, 1, 1, 3]))], None),
    ("histogram", paddle.histogram, {"x": M}, {"bins": 4},
     [np.histogram(M, bins=4)[0]], None),
    ("corrcoef", paddle.corrcoef, {"x": X}, {}, [np.corrcoef(X)], None),
    ("cov", paddle.cov, {"x": X}, {}, [np.cov(X)], None),
    ("cumulative_trapezoid", paddle.cumulative_trapezoid, {"y": M}, {},
     None, None),
    ("trapezoid", paddle.trapezoid, {"y": M}, {}, [np.trapezoid(M)], None),
    # --- linalg extras ------------------------------------------------------
    ("bmm", paddle.bmm, {"x": _randn(2, 3, 4), "y": _randn(2, 4, 5)}, {},
     None, ["x", "y"]),
    ("mv", paddle.mv, {"x": X, "vec": M[:5]}, {}, [X @ M[:5]], ["x", "vec"]),
    ("addmm", paddle.addmm,
     {"input": _randn(4, 4), "x": _randn(4, 5), "y": _randn(5, 4)}, {},
     None, ["input", "x", "y"]),
    ("matmul_t", lambda a, b: paddle.matmul(a, b, transpose_y=True),
     {"a": X, "b": X}, {}, [X @ X.T], ["a", "b"]),
    ("einsum", lambda a, b: paddle.einsum("ij,kj->ik", a, b),
     {"a": X, "b": X}, {}, [X @ X.T], None),
    ("tensordot", paddle.tensordot,
     {"x": _randn(3, 4, 5), "y": _randn(4, 5, 6)}, {},
     None, None),
    ("dist2", paddle.dist, {"x": X, "y": X * 0}, {},
     [np.linalg.norm(X)], None),
    ("cdist", paddle.cdist,
     {"x": _randn(3, 4), "y": _randn(5, 4)}, {}, None, None),
    ("renorm", paddle.renorm, {"x": X}, {"p": 2.0, "axis": 0,
                                         "max_norm": 1.0}, None, None),
    # --- sequence ops (LoD -> padded+mask, extension.py) -------------------
    ("sequence_mask", F.sequence_mask,
     {"x": np.array([2, 0, 3], np.int64)}, {"maxlen": 4},
     [np.array([[1, 1, 0, 0], [0, 0, 0, 0], [1, 1, 1, 0]], np.int64)], None),
    # --- metric ops ---------------------------------------------------------
    ("accuracy_k1", paddle.metric.accuracy,
     {"input": _np_softmax(_randn(6, 4)),
      "label": rng.randint(0, 4, (6, 1)).astype(np.int64)}, {"k": 1},
     None, None),
]
CASES = [c for c in CASES if c is not None]


@pytest.mark.parametrize("case", CASES, ids=[c[0] for c in CASES])
def test_op(case):
    name, op, inputs, attrs, outputs, grad_inputs = case
    t = OpTest()
    t.op = op
    t.inputs = inputs
    t.attrs = attrs
    t.outputs = outputs
    if outputs is not None:
        t.check_output(atol=1e-4, rtol=1e-4,
                       jit=name not in ("masked_select", "nonzero", "unique",
                                        "unique_consecutive", "mode",
                                        "bincount", "histogram"))
    if grad_inputs:
        t.check_grad(grad_inputs)


# --- cases needing bespoke references --------------------------------------

class TestFlattenRef(OpTest):
    def setUp(self):
        x = _randn(2, 3, 4)
        self.op = paddle.flatten
        self.inputs = {"x": x}
        self.attrs = {"start_axis": 1, "stop_axis": 2}
        self.outputs = [x.reshape(2, 12)]

    def test(self):
        self.check_output()
        self.check_grad(["x"])


class TestScatterRef(OpTest):
    def setUp(self):
        x = _randn(4, 5)
        upd = _randn(2, 5)
        idx = np.array([1, 3], np.int64)
        want = x.copy()
        want[idx] = upd
        self.op = paddle.scatter
        self.inputs = {"x": x, "index": idx, "updates": upd}
        self.outputs = [want]

    def test(self):
        self.check_output()


class TestSequencePadUnpadRoundtrip:
    def test_roundtrip(self):
        """sequence_pad: ragged list -> dense [b, maxlen] + lengths;
        sequence_unpad inverts it exactly (sequence_pad_op.cc parity)."""
        seqs = [np.array([1.0, 2.0], np.float32),
                np.array([3.0], np.float32),
                np.array([4.0, 5.0, 6.0], np.float32)]
        padded, lens = F.sequence_pad([paddle.to_tensor(s) for s in seqs],
                                      0.0)
        np.testing.assert_array_equal(np.asarray(lens._data), [2, 1, 3])
        want = np.array([[1, 2, 0], [3, 0, 0], [4, 5, 6]], np.float32)
        np.testing.assert_allclose(np.asarray(padded._data), want)
        back = F.sequence_unpad(padded, lens)
        for s, b in zip(seqs, back):
            np.testing.assert_allclose(np.asarray(b._data), s)

    def test_maxlen_truncates(self):
        seqs = [np.array([1.0, 2.0, 3.0], np.float32)]
        padded, lens = F.sequence_pad([paddle.to_tensor(s) for s in seqs],
                                      -1.0, maxlen=2)
        np.testing.assert_allclose(np.asarray(padded._data), [[1.0, 2.0]])
        np.testing.assert_array_equal(np.asarray(lens._data), [2])


class TestAccuracyValue(OpTest):
    def setUp(self):
        probs = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], np.float32)
        label = np.array([[1], [0], [0]], np.int64)
        self.op = paddle.metric.accuracy
        self.inputs = {"input": probs, "label": label}
        self.outputs = [np.float32(2.0 / 3.0)]

    def test(self):
        self.check_output()


class TestCdistGrad(OpTest):
    def setUp(self):
        self.op = paddle.cdist
        self.inputs = {"x": _randn(3, 4) * 2, "y": _randn(5, 4) * 2}
        self.outputs = None

    def test(self):
        self.check_grad(["x", "y"], atol=5e-3, rtol=5e-2)


class TestPutAlongAxis(OpTest):
    def setUp(self):
        x = _randn(3, 4)
        idx = rng.randint(0, 4, (3, 2)).astype(np.int64)
        vals = _randn(3, 2)
        want = x.copy()
        np.put_along_axis(want, idx, vals, axis=1)
        self.op = paddle.put_along_axis
        self.inputs = {"x": x, "indices": idx, "values": vals}
        self.attrs = {"axis": 1}
        self.outputs = [want]

    def test(self):
        self.check_output()


class TestCdistSelfGrad(OpTest):
    """Review r2g: cdist(x, x)'s zero diagonal must not NaN the gradient."""

    def test(self):
        x = paddle.to_tensor(_randn(4, 3))
        x.stop_gradient = False
        d = paddle.cdist(x, x)
        d.sum().backward()
        g = np.asarray(x.grad._data)
        assert np.all(np.isfinite(g)), g
