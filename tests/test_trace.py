"""Structured tracing + device cost accounting (ISSUE 5): span model
(nesting/ids/attrs), ring-buffer cap, serving end-to-end request traces,
trainer MFU joined from the cost registry, chrome-trace export with
parent/flow integrity, and the JSONL span-log round-trip."""
import json
import threading

import numpy as np
import pytest

import jax

import paddle_tpu as paddle
from paddle_tpu import monitor, trace
from paddle_tpu.trace import costs


@pytest.fixture(autouse=True)
def _traced():
    """Each test runs with tracing ON against a clean buffer/registry and
    leaves the process exactly as it found it (flag off by default)."""
    trace.clear()
    costs.reset()
    trace.enable()
    yield
    trace.disable()
    trace.clear()
    costs.reset()
    paddle.set_flags({"trace_log_path": ""})


def _tiny_gpt():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                    num_heads=2, max_seq_len=64, dropout=0.0)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


class TestSpanModel:
    def test_nesting_inherits_trace_and_parent(self):
        with trace.span("outer", subsystem="t", a=1) as outer:
            assert trace.current_span() is outer
            with trace.span("inner") as inner:
                assert inner.parent_id == outer.span_id
                assert inner.trace_id == outer.trace_id
        assert trace.current_span() is None
        rec = {s.name: s for s in trace.spans()}
        assert set(rec) == {"outer", "inner"}
        assert rec["outer"].attrs == {"a": 1}
        assert rec["outer"].end_ns >= rec["outer"].start_ns
        # inner closed first: buffer order is end order
        assert [s.name for s in trace.spans()] == ["inner", "outer"]

    def test_span_ids_unique_and_attrs_settable(self):
        with trace.span("a") as s1:
            s1.set(k="v", n=2)
        with trace.span("b") as s2:
            pass
        assert s1.span_id != s2.span_id
        assert s1.trace_id != s2.trace_id   # separate roots, separate traces
        assert s1.attrs == {"k": "v", "n": 2}

    def test_start_span_and_emit_explicit_parenting(self):
        root = trace.start_span("root", subsystem="t")
        child = trace.start_span("child", parent=root)
        child.end(done=True)
        trace.emit("retro", root.start_ns, root.start_ns + 1000,
                   parent=root, x=1)
        root.end()
        by_name = {s.name: s for s in trace.spans()}
        assert by_name["child"].parent_id == root.span_id
        assert by_name["child"].trace_id == root.trace_id
        assert by_name["retro"].parent_id == root.span_id
        assert by_name["retro"].end_ns - by_name["retro"].start_ns == 1000
        assert by_name["child"].attrs["done"] is True

    def test_end_is_idempotent(self):
        s = trace.start_span("once")
        s.end()
        first_end = s.end_ns
        s.end(ignored=1)
        assert s.end_ns == first_end
        assert sum(1 for x in trace.spans() if x.span_id == s.span_id) == 1
        assert "ignored" not in s.attrs

    def test_ring_buffer_cap_drops_oldest(self):
        old_cap = trace.capacity()
        try:
            trace.set_capacity(8)
            for i in range(20):
                with trace.span(f"s{i}"):
                    pass
            got = [s.name for s in trace.spans()]
            assert got == [f"s{i}" for i in range(12, 20)]
        finally:
            trace.set_capacity(old_cap)

    def test_disabled_is_noop(self):
        trace.disable()
        with trace.span("ghost") as s:
            s.set(a=1)
        assert not trace.spans()
        assert trace.start_span("ghost2").end() is not None

    def test_threads_get_independent_stacks(self):
        seen = {}

        def worker():
            with trace.span("w") as s:
                seen["parent"] = s.parent_id

        with trace.span("main"):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        # the worker's span must NOT parent onto main's stack
        assert seen["parent"] is None

    def test_callable_module_keeps_the_math_op(self):
        # paddle.trace was the matrix-trace op before the module existed
        x = paddle.to_tensor(np.arange(9, dtype=np.float32).reshape(3, 3))
        assert float(np.asarray(paddle.trace(x)._data)) == 12.0
        assert paddle.trace is trace


class TestServingRequestTrace:
    def test_request_lifecycle_spans_share_one_trace_id(self):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_gpt()
        eng = ServingEngine(m, max_batch=2)
        rng = np.random.RandomState(0)
        rids = [eng.submit(rng.randint(0, 64, (n,)).astype(np.int32),
                           max_new_tokens=4) for n in (5, 9)]
        res = eng.run_until_complete()
        for rid in rids:
            req = res[rid]
            assert req.trace_id is not None
            mine = [s for s in trace.spans() if s.trace_id == req.trace_id]
            names = {s.name for s in mine}
            assert {"request", "queue_wait", "prefill", "decode"} <= names
            root = next(s for s in mine if s.name == "request")
            assert root.attrs["finish_reason"] == "length"
            assert root.attrs["new_tokens"] == 4
            # every child parents back to the root
            for s in mine:
                if s.name != "request":
                    assert s.parent_id == root.span_id
            # 1 prefill token + 3 decode steps = max_new_tokens
            assert sum(1 for s in mine if s.name == "decode") == 3
        # the two requests got DISTINCT trace ids
        assert res[rids[0]].trace_id != res[rids[1]].trace_id

    def test_chunked_prefill_emits_chunk_spans(self):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_gpt()
        eng = ServingEngine(m, max_batch=2, prefill_chunk=4)
        rng = np.random.RandomState(0)
        rid = eng.submit(rng.randint(0, 64, (10,)).astype(np.int32),
                         max_new_tokens=2)
        eng.run_until_complete()
        req = eng.get_request(rid)
        chunks = [s for s in trace.spans()
                  if s.trace_id == req.trace_id
                  and s.name == "prefill_chunk"]
        assert len(chunks) == 3   # ceil(10 / 4)
        assert [c.attrs["offset"] for c in chunks] == [0, 4, 8]

    def test_breakdown_joins_cost_registry(self):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_gpt()
        eng = ServingEngine(m, max_batch=2)
        rng = np.random.RandomState(0)
        eng.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                   max_new_tokens=4)
        eng.run_until_complete()
        bd = eng.stats()["breakdown"]
        assert bd["wall_ms_total"] > 0
        assert "decode_greedy" in bd["kinds"] and "prefill" in bd["kinds"]
        # FLAGS_trace forced executables through the cost registry, so
        # the flops join is live and the serving-side MFU is finite
        row = bd["kinds"]["decode_greedy"]
        assert row["flops_per_call"] > 0
        assert np.isfinite(bd["mfu"]) and bd["mfu"] > 0
        fr = sum(r["wall_fraction"] for r in bd["kinds"].values())
        assert abs(fr - 1.0) < 1e-9

    def test_queue_wait_ends_at_admission_and_finish_while_queued(self):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_gpt()
        eng = ServingEngine(m, max_batch=1)
        rng = np.random.RandomState(0)
        r1 = eng.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                        max_new_tokens=2)
        r2 = eng.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                        max_new_tokens=2)
        assert eng.cancel(r2) is True   # finished while still queued
        eng.run_until_complete()
        req2 = eng.get_request(r2)
        mine2 = [s for s in trace.spans() if s.trace_id == req2.trace_id]
        root2 = next(s for s in mine2 if s.name == "request")
        assert root2.attrs["finish_reason"] == "cancelled"
        assert any(s.name == "queue_wait" for s in mine2)
        req1 = eng.get_request(r1)
        waits = [s for s in trace.spans()
                 if s.trace_id == req1.trace_id and s.name == "queue_wait"]
        assert len(waits) == 1 and "wait_ms" in waits[0].attrs


class TestTrainerCostJoin:
    def _trainer(self):
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        model = paddle.nn.Linear(4, 1)
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=model.parameters())
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])
        return SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                           mesh=mesh)

    def test_step_span_and_finite_mfu(self):
        tr = self._trainer()
        x = np.ones((2, 4), np.float32)
        y = np.zeros((2, 1), np.float32)
        tr.train_step(x, y)
        tr.train_step(x, y)
        steps = [s for s in trace.spans() if s.name == "train_step"]
        assert len(steps) == 2
        assert steps[0].attrs["source"] in ("fresh", "disk")
        assert steps[1].attrs["source"] == "memory"
        sig = steps[0].attrs["sig"]
        entry = costs.get("trainer", sig)
        assert entry is not None and entry["flops"] > 0
        st = tr.stats()
        assert st["steps"] == 2
        assert st["flops_per_step"] == entry["flops"]
        assert st["mfu"] is not None
        assert np.isfinite(st["mfu"]) and st["mfu"] > 0
        assert st["hbm"]["peak_bytes"] > 0
        assert st["breakdown"]["dispatch_ms_total"] >= 0

    def test_program_gauges_exported(self):
        monitor.reset()
        tr = self._trainer()
        tr.train_step(np.ones((2, 4), np.float32),
                      np.zeros((2, 1), np.float32))
        flops = monitor.default_registry().get("program_flops")
        assert flops is not None
        sites = {s.labels["site"] for s in flops.series()}
        assert "trainer" in sites
        hbm = monitor.default_registry().get("program_hbm_bytes")
        kinds = {s.labels["kind"] for s in hbm.series()
                 if s.labels.get("site") == "trainer"}
        assert {"peak", "argument", "output", "temp"} <= kinds

    def test_two_trainers_same_batch_sig_do_not_clobber(self):
        """The site-global cost table keys by batch signature only; each
        trainer must join its OWN executable's flops (metrics_dump --all
        runs several models at identical shapes)."""
        from paddle_tpu.distributed.mesh import build_mesh
        from paddle_tpu.distributed.spmd import SpmdTrainer

        paddle.seed(0)
        mesh = build_mesh((1,), ("dp",), devices=jax.devices()[:1])

        def trainer(model):
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters())
            return SpmdTrainer(model, opt, loss_fn=paddle.nn.MSELoss(),
                               mesh=mesh)

        small = trainer(paddle.nn.Linear(4, 1))
        big = trainer(paddle.nn.Sequential(
            paddle.nn.Linear(4, 64), paddle.nn.ReLU(),
            paddle.nn.Linear(64, 1)))
        x = np.ones((2, 4), np.float32)
        y = np.zeros((2, 1), np.float32)
        small.train_step(x, y)
        big.train_step(x, y)   # same batch sig, different executable
        f_small = small.stats()["flops_per_step"]
        f_big = big.stats()["flops_per_step"]
        assert f_small and f_big and f_small < f_big

    def test_peak_bytes_subtracts_donation_alias(self):
        """Donated buffers appear in both argument and output sizes;
        peak must not double-count them (the serving KV caches are the
        canonical case)."""
        import jax.numpy as jnp

        from paddle_tpu.framework import aot

        cj = aot.cached_jit(lambda c, x: (c + x, c.sum()), site="t",
                            label="donated", donate_argnums=(0,))
        cj.warm(jax.ShapeDtypeStruct((256, 256), jnp.float32),
                jax.ShapeDtypeStruct((256, 256), jnp.float32))
        e = costs.get("t", "donated")
        assert e is not None and e["alias_bytes"] > 0
        assert e["peak_bytes"] == (e["argument_bytes"] + e["output_bytes"]
                                   + e["temp_bytes"]
                                   + e["generated_code_bytes"]
                                   - e["alias_bytes"])

    def test_peak_flops_finite_and_overridable(self):
        assert costs.peak_flops() > 0
        paddle.set_flags({"device_peak_flops": 123.0})
        try:
            assert costs.peak_flops() == 123.0
        finally:
            paddle.set_flags({"device_peak_flops": 0.0})


class TestChromeExport:
    def test_export_loads_and_parents_resolve(self, tmp_path):
        from paddle_tpu.inference.serving import ServingEngine

        m = _tiny_gpt()
        eng = ServingEngine(m, max_batch=2)
        rng = np.random.RandomState(0)
        eng.submit(rng.randint(0, 64, (5,)).astype(np.int32),
                   max_new_tokens=3)
        eng.run_until_complete()
        path = str(tmp_path / "trace.json")
        trace.export_chrome(path)
        with open(path) as f:
            doc = json.load(f)
        slices = [e for e in doc["traceEvents"]
                  if e.get("cat") == "span" and e["ph"] == "X"]
        assert slices
        # the acceptance criterion: queue/prefill/decode slices in the
        # chrome JSON share the request's ONE trace_id
        lifecycle = [e for e in slices
                     if e["name"] in ("queue_wait", "prefill", "decode")]
        assert {e["name"] for e in lifecycle} == {"queue_wait", "prefill",
                                                  "decode"}
        assert len({e["args"]["trace_id"] for e in lifecycle}) == 1
        ids = {e["args"]["span_id"] for e in slices}
        for e in slices:
            parent = e["args"].get("parent_id")
            if parent is not None:
                assert parent in ids, (e["name"], parent)
        # flow chain: the request's spans are linked start->...->finish
        flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
        assert any(e["ph"] == "s" for e in flows)
        assert any(e["ph"] == "f" for e in flows)
        # counter samples from the step boundary
        assert any(e["ph"] == "C"
                   and e["name"] == "serving_batch_occupancy"
                   for e in doc["traceEvents"])
        # subsystem process naming
        meta = {e["args"]["name"] for e in doc["traceEvents"]
                if e["ph"] == "M"}
        assert "serving" in meta

    def test_old_profiler_export_uses_merged_exporter(self, tmp_path):
        from paddle_tpu import profiler

        profiler.start_profiler()
        with profiler.RecordEvent("outer"):
            with profiler.RecordEvent("inner"):
                pass
        with trace.span("aside", subsystem="t"):
            pass
        profiler.stop_profiler()
        path = str(tmp_path / "old_api.json")
        profiler.export_chrome_tracing(path)
        with open(path) as f:
            doc = json.load(f)
        host = [e for e in doc["traceEvents"] if e.get("cat") == "host"]
        assert {e["name"] for e in host} == {"outer", "inner"}
        # sorted by start time: outer begins before inner
        assert [e["name"] for e in host] == ["outer", "inner"]
        assert host[0]["args"]["depth"] == 0
        assert host[1]["args"]["depth"] == 1
        # the old API's output now carries span context too
        assert any(e.get("cat") == "span" and e["name"] == "aside"
                   for e in doc["traceEvents"])

    def test_profiler_summary_honors_sorted_by(self):
        from paddle_tpu import profiler

        with profiler.Profiler() as p:
            for _ in range(3):
                with profiler.RecordEvent("many_fast"):
                    pass
            import time as _t

            with profiler.RecordEvent("one_slow"):
                _t.sleep(0.02)
        by_total = p.summary(sorted_by="total")
        assert by_total[0]["name"] == "one_slow"
        by_calls = p.summary(sorted_by="calls")
        assert by_calls[0]["name"] == "many_fast"


class TestJsonlRoundTrip:
    def test_span_log_round_trips(self, tmp_path):
        log = str(tmp_path / "spans.jsonl")
        paddle.set_flags({"trace_log_path": log})
        with trace.span("outer", subsystem="t", a=1):
            with trace.span("inner"):
                pass
        paddle.set_flags({"trace_log_path": ""})
        recs = trace.load_spans(log)
        assert [r["name"] for r in recs] == ["inner", "outer"]
        live = {s.span_id: s for s in trace.spans()}
        for r in recs:
            s = live[r["span_id"]]
            assert r["trace_id"] == s.trace_id
            assert r["parent_id"] == s.parent_id
            assert r["attrs"] == s.attrs
            assert r["start_ns"] == s.start_ns
            assert r["end_ns"] == s.end_ns

    def test_checkpoint_spans_tagged_with_bytes(self, tmp_path):
        p = str(tmp_path / "w.pdparams")
        paddle.save({"w": paddle.to_tensor(np.ones(8, np.float32))}, p)
        paddle.load(p)
        names = [s.name for s in trace.spans()]
        assert "checkpoint/save" in names and "checkpoint/load" in names
        import os

        for s in trace.spans():
            if s.name.startswith("checkpoint/"):
                assert s.attrs["bytes"] == os.path.getsize(p)

    def test_collective_span_tagged_with_bytes(self):
        from paddle_tpu.distributed import collective

        collective.all_reduce(
            paddle.to_tensor(np.ones(4, np.float32)))
        sp = next(s for s in trace.spans()
                  if s.name == "collective/all-reduce")
        assert sp.attrs["bytes"] == 16
