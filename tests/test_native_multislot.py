"""Native C++ MultiSlot parser tests (framework/data_feed.cc parity checks)."""
import os

import numpy as np
import pytest

from paddle_tpu.io.multislot import InMemoryDataset


@pytest.fixture(scope="module")
def sample_file(tmp_path_factory):
    # two slots: int64 ids (ragged) + float32 label (len 1)
    p = tmp_path_factory.mktemp("ms") / "part-0"
    lines = []
    rng = np.random.RandomState(0)
    for i in range(100):
        n_ids = rng.randint(1, 6)
        ids = rng.randint(0, 1000, n_ids)
        label = float(i % 2)
        lines.append(f"{n_ids} " + " ".join(map(str, ids)) + f" 1 {label}")
    p.write_text("\n".join(lines) + "\n")
    return str(p), lines


def _make_ds(batch=16):
    ds = InMemoryDataset()
    ds.add_slot("ids", "int64")
    ds.add_slot("label", "float32")
    ds.set_batch_size(batch)
    return ds


class TestMultiSlot:
    def test_parse_file_counts(self, sample_file):
        path, lines = sample_file
        ds = _make_ds()
        ds.set_filelist([path])
        n = ds.load_into_memory()
        assert n == 100
        assert ds.get_memory_data_size() == 100

    def test_values_roundtrip(self, sample_file):
        path, lines = sample_file
        ds = _make_ds(batch=100)
        ds.set_filelist([path])
        ds.load_into_memory()
        batch = next(ds.batch_iter(return_mask=True))
        assert batch["ids"].shape[0] == 100
        # check first line's ids survive
        first = lines[0].split()
        n0 = int(first[0])
        np.testing.assert_array_equal(batch["ids"][0, :n0], np.array(first[1 : 1 + n0], dtype=np.int64))
        assert batch["ids_mask"][0, :n0].sum() == n0
        np.testing.assert_allclose(batch["label"][:4, 0], [0.0, 1.0, 0.0, 1.0])

    def test_parse_from_string(self):
        ds = _make_ds(batch=2)
        n = ds.load_from_string("2 7 9 1 1.0\n1 3 1 0.0\n")
        assert n == 2
        b = next(ds.batch_iter())
        np.testing.assert_array_equal(b["ids"][0, :2], [7, 9])
        np.testing.assert_allclose(b["label"][:, 0], [1.0, 0.0])

    def test_shuffle_preserves_multiset(self, sample_file):
        path, _ = sample_file
        ds = _make_ds(batch=100)
        ds.set_filelist([path])
        ds.load_into_memory()
        before = next(ds.batch_iter(return_mask=True))
        ds.local_shuffle(seed=42)
        after = next(ds.batch_iter(return_mask=True))
        # same multiset of labels, different order (very likely)
        assert sorted(before["label"][:, 0].tolist()) == sorted(after["label"][:, 0].tolist())
        assert not np.array_equal(before["label"][:, 0], after["label"][:, 0])
        # id/label pairing preserved: count total ids unchanged
        assert before["ids_mask"].sum() == after["ids_mask"].sum()

    def test_multithreaded_parse_matches(self, sample_file):
        path, _ = sample_file
        ds = _make_ds()
        ds.set_filelist([path])
        ds.set_thread(4)
        assert ds.load_into_memory() == 100

    def test_release_memory(self, sample_file):
        path, _ = sample_file
        ds = _make_ds()
        ds.set_filelist([path])
        ds.load_into_memory()
        ds.release_memory()
        assert ds.get_memory_data_size() == 0
