"""PS dataset runtime (VERDICT r1 #8): InMemoryDataset global shuffle through
the PS servers + train_from_dataset — in-process static-graph path and a real
2-server/2-worker subprocess cluster (data_set.cc + hogwild_worker.cc:195-211
parity)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.io.multislot import InMemoryDataset


class TestTrainFromDataset:
    def _slot_file(self, tmp_path, n=64):
        """Fixed-width slots: x (4 floats) + y (1 float), linear target."""
        rng = np.random.RandomState(0)
        w = np.array([1.0, -2.0, 0.5, 3.0], np.float32)
        lines = []
        for _ in range(n):
            x = rng.randn(4).astype(np.float32)
            y = float(x @ w)
            lines.append("4 " + " ".join(repr(float(v)) for v in x)
                         + f" 1 {y!r}")
        p = tmp_path / "part-0"
        p.write_text("\n".join(lines) + "\n")
        return str(p)

    def test_static_train_from_dataset(self, tmp_path):
        """The canonical PS-era script shape: static program + dataset feed
        (exe.train_from_dataset(program, dataset))."""
        f = self._slot_file(tmp_path)
        paddle.enable_static()
        try:
            main = paddle.static.Program()
            startup = paddle.static.Program()
            with paddle.static.program_guard(main, startup):
                x = paddle.static.data(name="x", shape=[None, 4],
                                       dtype="float32")
                y = paddle.static.data(name="y", shape=[None, 1],
                                       dtype="float32")
                pred = paddle.static.nn.fc(x, size=1)
                loss = paddle.mean(
                    paddle.nn.functional.square_error_cost(pred, y))
                opt = paddle.optimizer.SGD(learning_rate=0.1)
                opt.minimize(loss)

            ds = InMemoryDataset()
            ds.init(batch_size=16, use_var=[x, y])
            ds.set_filelist([f])
            assert ds.load_into_memory() == 64
            ds.local_shuffle(seed=3)

            exe = paddle.static.Executor()
            exe.run(startup)
            first = exe.train_from_dataset(main, ds, fetch_list=[loss])
            for _ in range(20):
                last = exe.train_from_dataset(main, ds, fetch_list=[loss])
            assert float(last[0]) < 0.1 * float(first[0])
        finally:
            paddle.disable_static()

    def test_instance_lines_roundtrip(self, tmp_path):
        """global_shuffle's text re-serialization must reproduce instances."""
        f = self._slot_file(tmp_path, n=8)
        ds = InMemoryDataset()
        ds.add_slot("x", "float32")
        ds.add_slot("y", "float32")
        ds.set_batch_size(8)
        ds.set_filelist([f])
        ds.load_into_memory()
        before = next(ds.batch_iter())
        lines = ds._instance_lines()
        ds2 = InMemoryDataset()
        ds2.add_slot("x", "float32")
        ds2.add_slot("y", "float32")
        ds2.set_batch_size(8)
        ds2.load_from_string("\n".join(lines) + "\n")
        after = next(ds2.batch_iter())
        np.testing.assert_allclose(after["x"], before["x"])
        np.testing.assert_allclose(after["y"], before["y"])

    def test_single_process_global_shuffle_is_local(self, tmp_path):
        f = self._slot_file(tmp_path, n=16)
        ds = InMemoryDataset()
        ds.add_slot("x", "float32")
        ds.add_slot("y", "float32")
        ds.set_filelist([f])
        ds.load_into_memory()
        ds.global_shuffle()  # no client, world 1 -> local shuffle
        assert ds.get_memory_data_size() == 16


@pytest.mark.slow
def test_ps_cluster_dataset(tmp_path):
    """2 servers + 2 workers: per-worker files, PS-routed global shuffle
    (each worker must end up seeing BOTH sources), sparse-embedding training
    from the dataset."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "tests", "ps_dataset_script.py")
    log_dir = str(tmp_path / "logs")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PS_DATASET_DIR"] = str(tmp_path)
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.fleet.launch",
         "--server_num", "2", "--worker_num", "2", "--log_dir", log_dir,
         script],
        cwd=repo, env=env, timeout=300, capture_output=True, text=True)
    logs = ""
    for i in range(2):
        with open(os.path.join(log_dir, f"workerlog.{i}")) as f:
            logs += f.read()
    assert proc.returncode == 0, (proc.stdout[-1500:], proc.stderr[-1500:],
                                  logs[-3000:])
    assert logs.count("GLOBAL_SHUFFLE_OK") == 2, logs
    assert logs.count("PS_DATASET_OK") == 2, logs
    # shuffle preserved the total instance count across the cluster
    counts = [int(tok.split("=")[1]) for tok in logs.split()
              if tok.startswith("n_after=")]
    assert sum(counts) == 64, counts


def test_infer_from_dataset_never_touches_params(tmp_path):
    """Review r2f: inference over a minimized program must not update it."""
    t = TestTrainFromDataset()
    f = t._slot_file(tmp_path)
    paddle.enable_static()
    try:
        main = paddle.static.Program()
        startup = paddle.static.Program()
        with paddle.static.program_guard(main, startup):
            x = paddle.static.data(name="x", shape=[None, 4], dtype="float32")
            y = paddle.static.data(name="y", shape=[None, 1], dtype="float32")
            pred = paddle.static.nn.fc(x, size=1)
            loss = paddle.mean(
                paddle.nn.functional.square_error_cost(pred, y))
            paddle.optimizer.SGD(learning_rate=0.1).minimize(loss)
        ds = InMemoryDataset()
        ds.init(batch_size=16, use_var=[x, y])
        ds.set_filelist([f])
        ds.load_into_memory()
        exe = paddle.static.Executor()
        exe.run(startup)
        before = {k: v.numpy().copy() for k, v in main.state_dict().items()}
        exe.infer_from_dataset(main, ds, fetch_list=[loss])
        after = main.state_dict()
        for k in before:
            np.testing.assert_array_equal(before[k], after[k].numpy())
    finally:
        paddle.disable_static()
