"""Tests: paddle.distribution, optimizer extras (EMA/ModelAverage/LookAhead),
new tensor fns (trapezoid/renorm), sequence ops, onnx export facade.

Mirrors the reference's test style (test_distribution.py, test_ema.py,
test_lookahead.py in python/paddle/fluid/tests/unittests/) — numpy references,
small shapes.
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import distribution as D


class TestDistribution:
    def test_normal(self):
        paddle.seed(7)
        n = D.Normal(0.0, 1.0)
        s = np.asarray(n.sample((4000,))._data)
        assert abs(s.mean()) < 0.1 and abs(s.std() - 1.0) < 0.1
        lp = float(np.asarray(n.log_prob(paddle.to_tensor(0.0))._data))
        assert abs(lp - (-0.5 * np.log(2 * np.pi))) < 1e-5
        ent = float(np.asarray(n.entropy()._data))
        assert abs(ent - 0.5 * (1 + np.log(2 * np.pi))) < 1e-5

    def test_normal_kl(self):
        a, b = D.Normal(0.0, 1.0), D.Normal(1.0, 2.0)
        kl = float(np.asarray(a.kl_divergence(b)._data))
        # closed form: log(s2/s1) + (s1^2 + (m1-m2)^2)/(2 s2^2) - 1/2
        want = np.log(2.0) + (1.0 + 1.0) / 8.0 - 0.5
        assert abs(kl - want) < 1e-5

    def test_uniform(self):
        u = D.Uniform(1.0, 3.0)
        paddle.seed(3)
        s = np.asarray(u.sample((2000,))._data)
        assert s.min() >= 1.0 and s.max() < 3.0
        assert abs(float(np.asarray(u.entropy()._data)) - np.log(2.0)) < 1e-6
        p = np.asarray(u.probs(paddle.to_tensor([0.0, 2.0]))._data)
        np.testing.assert_allclose(p, [0.0, 0.5], atol=1e-6)

    def test_categorical(self):
        logits = np.log(np.array([0.2, 0.3, 0.5], np.float32))
        c = D.Categorical(logits)
        ent = float(np.asarray(c.entropy()._data))
        want = -(0.2 * np.log(0.2) + 0.3 * np.log(0.3) + 0.5 * np.log(0.5))
        assert abs(ent - want) < 1e-5
        paddle.seed(11)
        s = np.asarray(c.sample((5000,))._data)
        assert abs((s == 2).mean() - 0.5) < 0.05
        c2 = D.Categorical(np.zeros(3, np.float32))
        kl = float(np.asarray(c.kl_divergence(c2)._data))
        assert kl > 0

    def test_categorical_batched_and_stable(self):
        c = D.Categorical(np.random.RandomState(0).randn(3, 5).astype(np.float32))
        assert list(c.sample((2,)).shape) == [2, 3]
        c2 = D.Categorical(np.array([0.0, -100.0], np.float32))
        lp = float(np.asarray(c2.log_prob(paddle.to_tensor(np.int64(1)))._data))
        assert np.isfinite(lp) and -100.5 < lp <= -99.9

    def test_log_prob_grad(self):
        mu = paddle.to_tensor(np.float32(0.5), stop_gradient=False)
        n = D.Normal(mu, 1.0)
        lp = n.log_prob(paddle.to_tensor(np.float32(1.5)))
        lp.backward()
        # d/dmu of -(v-mu)^2/2 = (v-mu) = 1.0
        assert abs(float(np.asarray(mu.grad._data)) - 1.0) < 1e-5


class TestOptimizerExtras:
    def _toy(self):
        lin = paddle.nn.Linear(4, 4)
        x = paddle.to_tensor(np.random.RandomState(0).randn(8, 4).astype(np.float32))
        return lin, x

    def test_ema_apply_restore(self):
        lin, x = self._toy()
        ema = paddle.optimizer.ExponentialMovingAverage(lin.parameters(), decay=0.5)
        opt = paddle.optimizer.SGD(learning_rate=0.1, parameters=lin.parameters())
        w0 = np.asarray(lin.weight._data).copy()
        for _ in range(3):
            loss = (lin(x) ** 2).mean() if hasattr(lin(x), "mean") else None
            loss = paddle.mean(lin(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ema.update()
        live = np.asarray(lin.weight._data).copy()
        with ema.apply():
            shadow = np.asarray(lin.weight._data).copy()
            assert not np.allclose(shadow, live)
        np.testing.assert_allclose(np.asarray(lin.weight._data), live)
        assert not np.allclose(live, w0)

    def test_model_average(self):
        lin, x = self._toy()
        ma = paddle.optimizer.ModelAverage(0.15, parameters=lin.parameters(),
                                           min_average_window=2, max_average_window=10)
        opt = paddle.optimizer.SGD(learning_rate=0.05, parameters=lin.parameters())
        for _ in range(4):
            loss = paddle.mean(lin(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            ma.update()
        live = np.asarray(lin.weight._data).copy()
        with ma.apply():
            avg = np.asarray(lin.weight._data).copy()
        assert not np.allclose(avg, live)
        np.testing.assert_allclose(np.asarray(lin.weight._data), live)

    def test_lookahead_converges(self):
        lin, x = self._toy()
        inner = paddle.optimizer.SGD(learning_rate=0.2, parameters=lin.parameters())
        opt = paddle.optimizer.LookAhead(inner, alpha=0.5, k=2)
        losses = []
        for _ in range(10):
            loss = paddle.mean(lin(x) ** 2)
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(np.asarray(loss._data)))
        assert losses[-1] < losses[0]


class TestNewTensorOps:
    def test_trapezoid(self):
        y = paddle.to_tensor(np.array([[1.0, 2.0, 3.0], [4.0, 5.0, 6.0]], np.float32))
        np.testing.assert_allclose(np.asarray(paddle.trapezoid(y)._data), [4.0, 10.0])
        x = paddle.to_tensor(np.array([0.0, 1.0, 3.0], np.float32))
        out = np.asarray(paddle.trapezoid(y, x=x)._data)
        np.testing.assert_allclose(out, np.trapezoid(np.asarray(y._data), np.asarray(x._data), axis=-1))
        ct = np.asarray(paddle.cumulative_trapezoid(y)._data)
        np.testing.assert_allclose(ct, [[1.5, 4.0], [4.5, 10.0]])
        # 1-D x along a non-last axis
        y0 = paddle.to_tensor(np.ones((3, 4), np.float32))
        x0 = paddle.to_tensor(np.array([0.0, 1.0, 3.0], np.float32))
        out0 = np.asarray(paddle.cumulative_trapezoid(y0, x=x0, axis=0)._data)
        np.testing.assert_allclose(out0[:, 0], [1.0, 3.0])

    def test_renorm(self):
        x = np.random.RandomState(1).randn(3, 5).astype(np.float32)
        out = np.asarray(paddle.renorm(paddle.to_tensor(x), 2.0, 0, 1.0)._data)
        norms = np.linalg.norm(out, axis=1)
        assert (norms <= 1.0 + 1e-5).all()
        # rows already under the cap are untouched
        small = x / (np.linalg.norm(x, axis=1, keepdims=True) * 2)
        out2 = np.asarray(paddle.renorm(paddle.to_tensor(small), 2.0, 0, 1.0)._data)
        np.testing.assert_allclose(out2, small, rtol=1e-5)


class TestSequenceOps:
    def test_pad_unpad_roundtrip(self):
        import paddle_tpu.nn.functional as F

        a = paddle.to_tensor(np.arange(6, dtype=np.float32).reshape(3, 2))
        b = paddle.to_tensor(np.arange(4, dtype=np.float32).reshape(2, 2))
        out, lens = F.sequence_pad([a, b], 0.0)
        assert list(out.shape) == [2, 3, 2]
        np.testing.assert_array_equal(np.asarray(lens._data), [3, 2])
        back = F.sequence_unpad(out, lens)
        np.testing.assert_allclose(np.asarray(back[0]._data), np.asarray(a._data))
        np.testing.assert_allclose(np.asarray(back[1]._data), np.asarray(b._data))

    def test_gather_tree(self):
        import paddle_tpu.nn.functional as F

        ids = paddle.to_tensor(np.array([[[2, 2], [6, 1]], [[3, 9], [5, 1]], [[0, 1], [9, 0]]], np.int64))
        parents = paddle.to_tensor(np.array([[[0, 0], [1, 1]], [[1, 0], [1, 0]], [[0, 0], [0, 1]]], np.int64))
        out = np.asarray(F.gather_tree(ids, parents)._data)
        # reference docstring example (operators/gather_tree_op.cc)
        want = np.array([[[2, 2], [1, 6]], [[3, 3], [5, 1]], [[0, 1], [9, 0]]])
        np.testing.assert_array_equal(out, want)


class TestOnnxFacade:
    def test_export_writes_onnx_and_native(self, tmp_path):
        lin = paddle.nn.Linear(3, 2)
        path = str(tmp_path / "model")
        spec = [paddle.static.InputSpec(shape=[1, 3], dtype="float32")]
        onnx_path = paddle.onnx.export(lin, path, input_spec=spec)
        import os
        assert os.path.exists(onnx_path)
        loaded = paddle.jit.load(path)
        x = paddle.to_tensor(np.ones((1, 3), np.float32))
        np.testing.assert_allclose(
            np.asarray(loaded(x)._data), np.asarray(lin(x)._data), rtol=1e-5
        )
